#!/usr/bin/env python
"""Chaos harness CLI: run a GuardedTrainer under injected faults and
print the structured summary as JSON — every robustness claim in
docs/resilience.md is checkable by rerunning this.

Examples
--------
# the acceptance scenario: NaN grads, a mid-save writer kill, one
# transient dispatch failure — final loss must track the fault-free
# twin within rtol 1e-2
python tools/chaos_run.py --steps 30 --nan-step 5 --nan-step 6 \
    --nan-step 7 --crash-save-step 8 --transient-step 11

# q8 quantized-collective path on the 8-device CPU mesh
python tools/chaos_run.py --steps 20 --nan-step 4 --q8

Exit code: 0 when the run completes and (with --check) the final loss
is within --rtol of the fault-free twin; 1 otherwise.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()


def build_model(seed):
    import paddle_tpu as fluid
    from paddle_tpu import layers
    main, start = fluid.Program(), fluid.Program()
    # never 0: random_seed=0 means "draw from os.urandom" (framework
    # convention), which would initialize the chaos run and its
    # fault-free twin with DIFFERENT weights and void the comparison
    main.random_seed = start.random_seed = seed + 1
    with fluid.unique_name.guard():
        with fluid.program_guard(main, start):
            x = layers.data("x", [16], dtype="float32")
            label = layers.data("label", [1], dtype="int64")
            h = layers.fc(x, size=32, act="relu")
            pred = layers.fc(h, size=4, act="softmax")
            loss = layers.mean(layers.cross_entropy(pred, label))
            fluid.optimizer.SGD(0.1).minimize(loss)
    return main, start, loss


def make_batches(n, seed, batch=16):
    import numpy as np
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        x = rng.rand(batch, 16).astype(np.float32)
        y = np.argmax(x[:, :4], 1).reshape(batch, 1).astype(np.int64)
        out.append({"x": x, "label": y})
    return out


def run_once(args, injector, q8):
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.resilience import GuardedTrainer, RetryPolicy
    main, start, loss = build_model(args.seed)
    scope = fluid.Scope()
    exe = fluid.Executor()
    program = main
    if q8:
        from paddle_tpu.parallel import make_mesh
        bs = fluid.BuildStrategy()
        bs.gradient_sync = "q8"
        program = fluid.CompiledProgram(main).with_data_parallel(
            build_strategy=bs,
            mesh=make_mesh({"dp": 4}, jax.devices()[:4]))
    trainer = GuardedTrainer(
        exe, program, loss, startup_program=start, scope=scope,
        checkpoint_dir=tempfile.mkdtemp(prefix="chaos-ckpt-"),
        checkpoint_every=args.checkpoint_every,
        rollback_after=args.rollback_after,
        retry=RetryPolicy(max_retries=args.max_retries,
                          base_delay=args.base_delay,
                          seed=args.seed),
        faults=injector, sync_saves=True)
    summary = trainer.train(make_batches(args.steps, args.seed))
    return summary


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--nan-step", type=int, action="append",
                    default=[], help="poison the feed at this step "
                    "(repeatable)")
    ap.add_argument("--transient-step", type=int, action="append",
                    default=[], help="fail the dispatch once at this "
                    "step (repeatable)")
    ap.add_argument("--crash-save-step", type=int, action="append",
                    default=[], help="kill the checkpoint writer at "
                    "this step (repeatable)")
    ap.add_argument("--checkpoint-every", type=int, default=2)
    ap.add_argument("--rollback-after", type=int, default=3)
    ap.add_argument("--max-retries", type=int, default=3)
    ap.add_argument("--base-delay", type=float, default=0.05)
    ap.add_argument("--q8", action="store_true",
                    help="train through the q8 quantized collective "
                    "on a 4-device CPU mesh")
    ap.add_argument("--no-check", dest="check", action="store_false",
                    help="skip the fault-free twin comparison")
    ap.add_argument("--rtol", type=float, default=1e-2)
    args = ap.parse_args()

    from paddle_tpu.resilience import FaultInjector, TrainingAborted
    injector = FaultInjector(seed=args.seed)
    if args.nan_step:
        injector.nan_grad_at(*args.nan_step)
    for s in args.transient_step:
        injector.transient_dispatch_at(s, times=1)
    for s in args.crash_save_step:
        injector.crash_save_at(s, after_files=1)

    report = {"ok": False}
    try:
        summary = run_once(args, injector, args.q8)
        report["chaos"] = summary
        report["ok"] = summary["aborted"] is None
        if args.check:
            clean = run_once(args, None, args.q8)
            report["fault_free_final_loss"] = clean["final_loss"]
            a, b = summary["final_loss"], clean["final_loss"]
            rel = abs(a - b) / max(abs(b), 1e-12)
            report["final_loss_rel_diff"] = rel
            report["ok"] = report["ok"] and rel <= args.rtol
    except TrainingAborted as e:
        report["chaos"] = e.report
        report["aborted"] = e.reason
    print(json.dumps(report, indent=2, default=str))
    sys.exit(0 if report["ok"] else 1)


if __name__ == "__main__":
    main()
