#!/bin/bash
# Chip-window watcher: patiently waits for the axon-tunneled TPU to
# become claimable, then captures the round's perf evidence in one
# shot (bench.py headline+mixes, then the kernel win table). Designed
# around the observed outage modes: claims BLOCK (not fail), and
# killing a claim mid-flight leaves a stale lease that blocks the next
# one — so probes get long timeouts and long cool-downs between tries.
#
#   bash tools/chip_window.sh [logfile]
#
# Stops after one successful capture, when $STOP_FILE appears, or
# after MAX_HOURS. Exit 0 = captured; 3 = gave up.
set -u
LOG="${1:-/root/repo/chip_window.log}"
STOP_FILE="/root/repo/.stop_prober"
MAX_HOURS="${MAX_HOURS:-6}"
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))

say() { echo "[chip_window $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    [ -e "$STOP_FILE" ] && { say "stop file present — exiting"; exit 3; }
    say "probing for a claim (timeout 900s)..."
    if timeout 900 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
x = jnp.ones((512, 512), jnp.bfloat16)
(x @ x).sum().block_until_ready()
print('CLAIM_OK', d.device_kind)
" >>"$LOG" 2>&1 && grep -q CLAIM_OK "$LOG"; then
        say "window open — running bench.py"
        python bench.py >>"$LOG" 2>&1
        say "bench done — running kernel table"
        KERNEL_TABLE_STALL_S=360 timeout 3000 \
            python tools/kernel_table.py --json >>"$LOG" 2>&1
        say "capture complete"
        exit 0
    fi
    say "no claim — cooling down 300s (stale-lease expiry)"
    sleep 300
done
say "deadline reached without a window"
exit 3
