"""Profile the flagship transformer-base train step on the current
backend: capture the XLA device trace over a few scan'd steps and
print the per-op device-time table (profiler.device_summary_table).
Usage: python tools/profile_step.py [--iters 20] [--batch 64]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--trace-dir", default="/tmp/flagship_trace")
    args = ap.parse_args()

    import paddle_tpu as fluid
    from paddle_tpu import profiler
    from paddle_tpu.contrib import mixed_precision as amp
    from paddle_tpu.models import transformer as T

    cfg = T.TransformerConfig(src_vocab=30000, tgt_vocab=30000,
                              max_len=256, d_model=512, d_ffn=2048,
                              n_head=8, n_layer=6, dropout=0.1)
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = 1
    with fluid.program_guard(main_p, startup):
        avg_cost, _tok, _ = T.transformer(cfg)
        opt = amp.decorate(fluid.optimizer.AdamOptimizer(1e-3))
        opt.minimize(avg_cost)
    exe = fluid.Executor()
    exe.run(startup)
    import jax.numpy as jnp
    feed = {k: jnp.asarray(v)
            for k, v in T.make_fake_batch(cfg, args.batch).items()}
    run = lambda k: exe.run_repeated(main_p, feed=feed,  # noqa: E731
                                     fetch_list=[avg_cost], iters=k)
    print("compiling + warmup...", file=sys.stderr, flush=True)
    run(args.iters)
    print("tracing...", file=sys.stderr, flush=True)
    profiler.start_profiler("All", trace_path=args.trace_dir)
    run(args.iters)
    profiler.stop_profiler()
    print(profiler.device_summary_table())


if __name__ == "__main__":
    main()
