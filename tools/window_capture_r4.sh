#!/bin/bash
# Round-4b chip-window capture: waits for the axon tunnel to come
# back (claims BLOCK rather than fail; killed claims leave stale
# leases, so probes get long timeouts and cool-downs — the
# chip_window.sh pattern), then captures in order:
#   1. the f32+dropout finite-difference check of the attention
#      dropout-seed fix (fwd/bwd G consistency),
#   2. bench.py (headline + per-mix evidence lines, new mix list),
#   3. bench.py --all (BERT with the gray-listed lean xent, ResNet,
#      MNIST, DeepFM),
#   4. tools/mem_estimate.py resnet50 64 96 128 (compile-only).
set -u
LOG="${1:-/root/repo/.window_capture_r4.log}"
STOP_FILE="/root/repo/.stop_prober"
MAX_HOURS="${MAX_HOURS:-6}"
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))
cd /root/repo

say() { echo "[capture $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    [ -e "$STOP_FILE" ] && { say "stop file present — exiting"; exit 3; }
    say "probing for a claim (timeout 900s)..."
    if timeout 900 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
x = jnp.ones((512, 512), jnp.bfloat16)
(x @ x).sum().block_until_ready()
print('CLAIM_OK', d.device_kind)
" >>"$LOG" 2>&1 && tail -5 "$LOG" | grep -q CLAIM_OK; then
        say "window open — FD dropout check"
        timeout 1800 python tools/fd_dropout_check.py >>"$LOG" 2>&1
        say "bench headline"
        timeout 2400 python bench.py >>"$LOG" 2>&1
        say "bench --all"
        timeout 3600 python bench.py --all >>"$LOG" 2>&1
        say "resnet mem estimates"
        timeout 2400 python tools/mem_estimate.py resnet50 96 128 \
            >>"$LOG" 2>&1
        say "capture complete"
        exit 0
    fi
    say "no claim — cooling down 300s (stale-lease expiry)"
    sleep 300
done
say "deadline reached without a window"
exit 3
