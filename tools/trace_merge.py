#!/usr/bin/env python
"""Merge per-process chrome traces into ONE fleet timeline.

Each process's ``profiler.export_chrome_tracing`` output is
self-relative (perf_counter epoch). This tool rebases them onto a
common wall-clock axis using:

  1. the ``clock_sync`` metadata each trace carries
     (``{wall_time_s, trace_ts_us, role}`` — the wall↔trace-ts
     correspondence captured at export), and
  2. optional per-process event journals: paired ``heartbeat_rtt``
     (trainer: t0/t1 around the beat) and ``heartbeat_recv`` (pserver:
     its local receive time) events estimate each server clock's
     OFFSET against the trainer clocks — ``offset = t_recv -
     (t0+t1)/2`` at the minimum-RTT beat, the classic NTP-style
     estimate. Without journals, wall clocks are trusted as-is
     (same-host processes).

Cross-process span correlation: ``rpc_client:*`` spans carry
``args.trace``/``args.span`` and the server's ``rpc_server:*`` spans
carry the same ``args.trace`` (+ ``parent_span``), so the merged
timeline draws chrome FLOW arrows from each client span to the
handler spans it caused.

    python tools/trace_merge.py --out merged.json \
        trace.trainer-0.json trace.pserver-0.json \
        --journal events.trainer-0.jsonl \
        --journal events.pserver-0.jsonl

Prints a JSON report {processes, events, links, offsets_s, out}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _clock_sync(trace):
    for e in trace.get("traceEvents", []):
        if e.get("name") == "clock_sync" and e.get("ph") == "M":
            return e.get("args", {})
    return {}


def estimate_offsets(journals):
    """role -> clock offset seconds vs the trainer clocks (positive:
    that role's clock runs ahead). Pairs heartbeat_rtt/heartbeat_recv
    by (tid, beat) and takes the min-RTT beat per server role.
    Deliberately NOT keyed on endpoint: the trainer journals the
    address it DIALED (a proxy, an alias, localhost-vs-127.0.0.1)
    while the server journals its BIND address, so endpoint strings
    need not match across journals — instead HeartbeatThread assigns
    each endpoint's beats from a disjoint range, making (tid, beat)
    unique fleet-wide."""
    rtts = {}   # (tid, beat) -> (t0, t1)
    recvs = {}  # (tid, beat) -> (t_recv, server_role)
    for events in journals:
        for e in events:
            if e.get("kind") == "heartbeat_rtt":
                key = (e.get("tid"), e.get("beat"))
                rtts[key] = (e.get("t0_wall"), e.get("t1_wall"))
            elif e.get("kind") == "heartbeat_recv":
                key = (e.get("tid"), e.get("beat"))
                recvs[key] = (e.get("t_wall"), e.get("role"))
    best = {}  # server role -> (rtt, offset)
    for key, (t0, t1) in rtts.items():
        hit = recvs.get(key)
        if hit is None or t0 is None or t1 is None:
            continue
        t_recv, role = hit
        rtt = t1 - t0
        offset = t_recv - (t0 + t1) / 2.0
        if role not in best or rtt < best[role][0]:
            best[role] = (rtt, offset)
    return {role: off for role, (_rtt, off) in best.items()}


def merge(trace_paths, journal_paths=(), out_path=None):
    from paddle_tpu.observability import read_journal
    traces = []
    for p in trace_paths:
        with open(p) as f:
            traces.append((p, json.load(f)))
    journals = [read_journal(p) for p in journal_paths]
    offsets = estimate_offsets(journals)

    # wall time of each trace's ts==0, corrected onto the reference
    # (trainer) clock by subtracting the role's estimated offset
    anchors = []
    for p, tr in traces:
        cs = _clock_sync(tr)
        role = cs.get("role") or os.path.basename(p)
        wall0 = (cs.get("wall_time_s", 0.0)
                 - cs.get("trace_ts_us", 0.0) / 1e6
                 - offsets.get(role, 0.0))
        anchors.append((p, tr, role, wall0))
    t_ref = min(w for _, _, _, w in anchors) if anchors else 0.0

    merged = []
    client_spans = {}  # trace id -> [event]
    server_spans = {}
    links = 0
    for i, (p, tr, role, wall0) in enumerate(anchors):
        shift_us = (wall0 - t_ref) * 1e6
        pid_map = {}
        for e in tr.get("traceEvents", []):
            e = dict(e)
            old_pid = e.get("pid", 0)
            pid = pid_map.setdefault(
                old_pid, 10 * i + (old_pid if isinstance(old_pid, int)
                                   else 0))
            e["pid"] = pid
            if e.get("ph") == "M":
                if e.get("name") == "process_name":
                    e["args"] = {"name": "%s: %s" % (
                        role, e.get("args", {}).get("name", ""))}
                merged.append(e)
                continue
            if "ts" in e:
                e["ts"] = e["ts"] + shift_us
            merged.append(e)
            tid_arg = (e.get("args") or {}).get("trace")
            if tid_arg:
                name = e.get("name", "")
                if name.startswith("rpc_client:"):
                    client_spans.setdefault(tid_arg, []).append(e)
                elif name.startswith("rpc_server:"):
                    server_spans.setdefault(tid_arg, []).append(e)

    # flow arrows: client span -> handler span(s) on the same trace id
    # (parent_span narrows to the exact causal client span when the
    # trace spans several RPCs)
    flow_id = 0
    flows = []
    for trace_id, servers in server_spans.items():
        clients = client_spans.get(trace_id, [])
        if not clients:
            continue
        by_span = {c["args"].get("span"): c for c in clients}
        for s in servers:
            c = by_span.get((s.get("args") or {}).get("parent_span"))
            if c is None:
                c = min(clients, key=lambda e: e.get("ts", 0.0))
            flow_id += 1
            links += 1
            base = {"cat": "rpc_flow", "name": "rpc", "id": flow_id}
            flows.append(dict(base, ph="s", ts=c["ts"], pid=c["pid"],
                              tid=c.get("tid", 0)))
            flows.append(dict(base, ph="f", bp="e", ts=s["ts"],
                              pid=s["pid"], tid=s.get("tid", 0)))
    merged.extend(flows)

    out = {"traceEvents": merged,
           "metadata": {"clock_offsets_s": offsets,
                        "processes": [r for _, _, r, _ in anchors]}}
    if out_path:
        d = os.path.dirname(out_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(out, f)
    report = {"processes": len(anchors),
              "events": len(merged),
              "links": links,
              "offsets_s": {k: round(v, 6)
                            for k, v in offsets.items()},
              "out": out_path}
    return out, report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("traces", nargs="+",
                    help="per-process chrome trace JSON files")
    ap.add_argument("--journal", action="append", default=[],
                    help="per-process event journal (repeatable; "
                    "enables heartbeat-RTT clock-offset estimation)")
    ap.add_argument("--out", default="merged_trace.json")
    args = ap.parse_args(argv)
    _, report = merge(args.traces, args.journal, args.out)
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
