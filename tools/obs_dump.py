#!/usr/bin/env python
"""Dump one JSON observability snapshot: registry metrics + merged
event journals.

Inputs (combine freely):

  --metrics URL|FILE   a Prometheus /metrics endpoint (the
                       observability.start_metrics_server thread) or a
                       saved exposition-text file; parsed into
                       {metric{labels}: value} ("_bucket/_sum/_count"
                       series stay flat — this is a dump, not a TSDB).
  --journal PATH       a JSONL event journal (repeatable — one per
                       worker process); events from every journal are
                       merged into one wall-clock-ordered tail.
  --tail N             events to keep in the merged tail (default 50).

Example (after a launch.py run with --journal_dir logs/):

    python tools/obs_dump.py --journal logs/events.trainer-0.jsonl \
        --journal logs/events.pserver-0.jsonl --tail 20

Prints ONE JSON object:
  {"metrics": {...}|null,
   "journals": {path: {"events": n, "role": ..., "kinds": {...}}},
   "tail": [ ...merged events, oldest first... ]}
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def parse_prometheus_text(text):
    """Exposition text -> {"series": {name{labels}: value},
    "types": {name: kind}}. Tolerant: malformed lines are skipped."""
    series, types = {}, {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        key, _, val = line.rpartition(" ")
        if not key:
            continue
        try:
            series[key] = float(val)
        except ValueError:
            continue
    return {"series": series, "types": types}


def load_metrics(src):
    if src.startswith(("http://", "https://")):
        import urllib.request
        with urllib.request.urlopen(src, timeout=5) as r:
            text = r.read().decode()
    else:
        with open(src) as f:
            text = f.read()
    return parse_prometheus_text(text)


def summarize_journal(events):
    kinds = {}
    for e in events:
        kinds[e.get("kind", "?")] = kinds.get(e.get("kind", "?"), 0) + 1
    roles = sorted({e.get("role", "?") for e in events})
    return {"events": len(events),
            "role": roles[0] if len(roles) == 1 else roles,
            "kinds": kinds}


def dump(metrics_src=None, journal_paths=(), tail=50):
    from paddle_tpu.observability import read_journal
    out = {"metrics": None, "journals": {}, "tail": []}
    if metrics_src:
        out["metrics"] = load_metrics(metrics_src)
    merged = []
    for path in journal_paths:
        events = read_journal(path)
        out["journals"][path] = summarize_journal(events)
        merged.extend(events)
    # wall clock first (cross-process), per-process seq as tiebreak
    merged.sort(key=lambda e: (e.get("t_wall", 0.0),
                               e.get("role", ""), e.get("seq", 0)))
    out["tail"] = merged[-int(tail):] if tail else merged
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--metrics", default=None,
                    help="/metrics URL or exposition-text file")
    ap.add_argument("--journal", action="append", default=[],
                    help="JSONL event journal (repeatable)")
    ap.add_argument("--tail", type=int, default=50)
    args = ap.parse_args(argv)
    print(json.dumps(dump(args.metrics, args.journal, args.tail),
                     indent=2, default=repr))
    return 0


if __name__ == "__main__":
    sys.exit(main())
