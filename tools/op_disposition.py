"""Op-disposition audit: every reference REGISTER_OPERATOR name is
accounted for.

The reference registers 404 operator names (extracted from
paddle/fluid/operators — see docs/ref_op_names.txt for the exact
command; name source: paddle/fluid/framework/op_registry.h:197). This
tool maps EVERY name to exactly one disposition:

  implemented   — same name in paddle_tpu's op registry
  implemented-as— capability registered under a different name
  autodiff      — a *_grad/*_grad2 name; gradients come from
                  backward.py jax.vjp-based autodiff, not registered
                  grad ops (the base op must itself be accounted)
  replaced-by   — capability delivered by a different tpu-native
                  mechanism (named in the note)
  delegated     — XLA/PJRT provides it (fusion, liveness, layout)
  scoped-out    — vendor dead end per SURVEY (named reason)
  artifact      — grep artifact, not a real operator

    python tools/op_disposition.py          # regenerate docs/op_disposition.md
    python tools/op_disposition.py --check  # verify doc current + none unaccounted

tests/test_op_disposition.py runs the --check path; an unaccounted
name (e.g. after editing docs/ref_op_names.txt) fails CI — the
API.spec discipline applied to ops.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_NAMES = os.path.join(_REPO, "docs", "ref_op_names.txt")
DOC = os.path.join(_REPO, "docs", "op_disposition.md")

_PS = ("distributed/ps.py ListenAndServ + distributed/rpc.py verbs "
       "over native/tensor_rpc.cpp")
_LOD = ("padded+lengths sequence representation (ops/sequence_ops.py; "
        "lod_tensor.py migration bridge)")
_XLA_FUSE = ("XLA automatic fusion over the unfused lowerings; "
             "ir/passes.py holds the pattern-level fusion passes")
_ENGINE = ("vendor inference engine subgraph op (SURVEY §2 dead end); "
           "inference is inference/AnalysisPredictor on XLA")

# name -> (disposition, note). Only names NOT in the live registry and
# NOT *_grad need an entry here.
MANUAL = {
    "op_type": ("artifact",
                "literal macro parameter (isfinite_op.cc:98, "
                "elementwise_op.h:368), not an operator"),
    # vendor engines / NCCL legacy
    "anakin_engine": ("scoped-out", _ENGINE),
    "ngraph_engine": ("scoped-out", _ENGINE),
    "tensorrt_engine": ("scoped-out", _ENGINE),
    "nccl": ("replaced-by",
             "mesh collectives via GSPMD (parallel/mesh.py, "
             "compiler.py CompiledProgram)"),
    "gen_nccl_id": ("replaced-by",
                    "jax.distributed bootstrap (parallel/multihost.py "
                    "init_parallel_env)"),
    # memory / executor plumbing
    "alloc_continuous_space": (
        "delegated",
        "XLA buffer assignment owns contiguity; fused-collective "
        "staging buffers unneeded under GSPMD"),
    "delete_var": ("delegated",
                   "XLA liveness analysis + core/scope.py drop_all"),
    "feed": ("replaced-by", "executor.py feed binding (jit arguments "
             "with donation)"),
    "fetch": ("replaced-by", "executor.py fetch_list (jit outputs)"),
    "read": ("replaced-by", "pyreader.py PyReader"),
    "create_custom_reader": ("replaced-by",
                             "reader/decorator.py composable readers"),
    "get_places": ("replaced-by",
                   "core places + parallel/mesh.py device enumeration"),
    "load": ("replaced-by", "io.py load_vars/load_persistables"),
    "load_combine": ("replaced-by", "io.py combined checkpoint files"),
    "save": ("replaced-by", "io.py save_vars/save_persistables"),
    "save_combine": ("replaced-by", "io.py combined checkpoint files"),
    # LoD machinery -> padded+lengths
    "array_to_lod_tensor": ("replaced-by", _LOD),
    "lod_tensor_to_array": ("replaced-by", _LOD),
    "lod_array_length": ("replaced-by", _LOD),
    "lod_rank_table": ("replaced-by", _LOD),
    "lod_reset": ("replaced-by", _LOD),
    "max_sequence_len": ("replaced-by", _LOD),
    "reorder_lod_tensor_by_rank": ("replaced-by", _LOD),
    "shrink_rnn_memory": ("replaced-by",
                          "lax.scan carries RNN state (layers/rnn.py); "
                          "no per-step memory shrink op needed"),
    "rnn_memory_helper": ("replaced-by",
                          "lax.scan carries RNN state (layers/rnn.py)"),
    "merge_lod_tensor": ("replaced-by",
                         "IfElse lowering to lax.select/cond "
                         "(layers/control_flow.py)"),
    "split_lod_tensor": ("replaced-by",
                         "IfElse lowering to lax.select/cond "
                         "(layers/control_flow.py)"),
    "write_to_array": ("replaced-by",
                       "TensorArray on lax.scan stacking "
                       "(layers/control_flow.py)"),
    "read_from_array": ("replaced-by",
                        "TensorArray on lax.scan stacking "
                        "(layers/control_flow.py)"),
    # control flow
    "conditional_block": ("replaced-by",
                          "lax.cond lowering (layers/control_flow.py)"),
    "recurrent": ("replaced-by",
                  "StaticRNN/DynamicRNN on lax.scan (layers/rnn.py, "
                  "layers/control_flow.py)"),
    # CPU/cuDNN fusion kernels -> XLA fusion
    "attention_lstm": ("delegated", _XLA_FUSE),
    "cudnn_lstm": ("replaced-by",
                   "lstm op on lax.scan (vendor cuDNN binding "
                   "unneeded; XLA compiles the scan)"),
    "fused_embedding_fc_lstm": ("delegated", _XLA_FUSE),
    "fused_embedding_seq_pool": ("delegated", _XLA_FUSE),
    "fusion_gru": ("delegated", _XLA_FUSE),
    "fusion_repeated_fc_relu": ("delegated", _XLA_FUSE),
    "fusion_seqconv_eltadd_relu": ("delegated", _XLA_FUSE),
    "fusion_seqexpand_concat_fc": ("delegated", _XLA_FUSE),
    "fusion_squared_mat_sub": ("delegated", _XLA_FUSE),
    "conv2d_inception_fusion": ("delegated", _XLA_FUSE),
    # distributed PS verbs
    "checkpoint_notify": ("replaced-by", _PS),
    "fetch_barrier": ("replaced-by", _PS),
    "listen_and_serv": ("replaced-by", _PS),
    "prefetch": ("replaced-by", _PS),
    "recv": ("replaced-by", _PS),
    "send": ("replaced-by", _PS),
    "send_barrier": ("replaced-by", _PS),
    "split_byref": ("replaced-by",
                    "transpiler/ VarBlock slicing"),
    "split_ids": ("replaced-by",
                  "distributed/lookup_service.py LargeScaleKV + "
                  "distributed/sparse.py id sharding"),
    "merge_ids": ("replaced-by",
                  "distributed/lookup_service.py LargeScaleKV + "
                  "distributed/sparse.py id sharding"),
    "lookup_sparse_table": ("replaced-by",
                            "distributed/lookup_service.py "
                            "LargeScaleKV"),
    "fake_init": ("replaced-by",
                  "distributed/lookup_service.py lazy row init"),
    "split_selected_rows": ("replaced-by",
                            "core/selected_rows.py + transpiler "
                            "slicing"),
    # int8 quantization runtime ops (mkldnn)
    "quantize": ("replaced-by",
                 "contrib/slim quantization (fake_quantize_* ops are "
                 "registered; int8 runtime conversion is XLA's)"),
    "dequantize": ("replaced-by",
                   "contrib/slim quantization (fake_quantize_* ops "
                   "are registered)"),
    "requantize": ("scoped-out",
                   "mkldnn int8 re-scale kernel (vendor dead end per "
                   "SURVEY)"),
    # misc
    "assign_value": ("implemented-as", "assign_numpy_value"),
    "detection_map": ("replaced-by",
                      "metrics.DetectionMAP / layers/detection.py "
                      "(host-side metric on this substrate)"),
}


def load_ref_names():
    names = []
    with open(REF_NAMES) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                names.append(line)
    return sorted(set(names))


def _grad_base(name):
    base = name
    while True:
        if base.endswith("_grad"):
            base = base[:-5]
        elif base.endswith("_grad2"):
            base = base[:-6]
        else:
            return base if base != name else None


def audit():
    """Return (rows, unaccounted). rows: [(name, disposition, note)]."""
    from paddle_tpu.ops import registry
    ours = set(registry.all_op_types())
    names = load_ref_names()
    accounted = {}
    for name in names:
        if name in ours:
            accounted[name] = ("implemented", "ops registry")
        elif name in MANUAL:
            accounted[name] = MANUAL[name]
    unaccounted = []
    rows = []
    for name in names:
        if name in accounted:
            rows.append((name,) + accounted[name])
            continue
        base = _grad_base(name)
        if base is not None and (base in accounted or base in ours):
            rows.append((name, "autodiff",
                         "grad of %s via backward.py jax.vjp" % base))
        else:
            rows.append((name, "UNACCOUNTED", ""))
            unaccounted.append(name)
    return rows, unaccounted


def render(rows):
    from collections import Counter
    counts = Counter(d for _, d, _ in rows)
    out = []
    out.append("# Op disposition: reference REGISTER_OPERATOR names "
               "→ paddle_tpu\n")
    out.append("Generated by `python tools/op_disposition.py`; "
               "checked by `tests/test_op_disposition.py`. Name "
               "source: docs/ref_op_names.txt (404 names from the "
               "reference's registration macros, "
               "paddle/fluid/framework/op_registry.h:197).\n")
    order = ["implemented", "implemented-as", "autodiff", "replaced-by",
             "delegated", "scoped-out", "artifact", "UNACCOUNTED"]
    summary = " / ".join("%s %d" % (k, counts[k])
                         for k in order if counts.get(k))
    out.append("**%d names: %s.**\n" % (len(rows), summary))
    for cat in order:
        sub = [r for r in rows if r[1] == cat]
        if not sub:
            continue
        out.append("\n## %s (%d)\n" % (cat, len(sub)))
        if cat == "implemented":
            # compact: these are 1:1 registry names
            namelist = ", ".join("`%s`" % n for n, _, _ in sub)
            out.append(namelist + "\n")
            continue
        if cat == "autodiff":
            out.append("Gradient names; gradients are produced by "
                       "`backward.py`'s jax.vjp-based autodiff over "
                       "the base op's lowering, not by registered "
                       "grad ops.\n\n")
            namelist = ", ".join("`%s`" % n for n, _, _ in sub)
            out.append(namelist + "\n")
            continue
        out.append("| name | note |\n|---|---|\n")
        for n, _, note in sub:
            out.append("| `%s` | %s |\n" % (n, note))
    return "".join(out)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    rows, unaccounted = audit()
    text = render(rows)
    if "--check" in argv:
        ok = True
        if unaccounted:
            print("UNACCOUNTED ops:", ", ".join(unaccounted))
            ok = False
        try:
            current = open(DOC).read()
        except OSError:
            current = ""
        if current != text:
            print("docs/op_disposition.md is stale — rerun "
                  "python tools/op_disposition.py")
            ok = False
        return 0 if ok else 1
    with open(DOC, "w") as f:
        f.write(text)
    print("wrote %s (%d names, %d unaccounted)"
          % (DOC, len(rows), len(unaccounted)))
    return 1 if unaccounted else 0


if __name__ == "__main__":
    sys.exit(main())
