"""Chip-measured refer-vs-pallas win table at flagship shapes.

The analog of the reference's operators/jit/benchmark.cc +
jit/README.en.md discipline: every kernel in the default library mix
must WIN at its target shape, proven by an in-tree benchmark table.
Run on the real chip:

    python tools/kernel_table.py            # all kernels, markdown out
    python tools/kernel_table.py --json     # machine-readable lines

Each row times the base XLA lowering against the pallas variant
through the real executor (fwd+bwd where differentiable) at the
transformer-base flagship shape, and verdicts win/lose. Paste the
table into BASELINE.md and demote any loser from the default mix.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import ml_dtypes  # noqa: E402
import numpy as np  # noqa: E402

_BF16 = ml_dtypes.bfloat16

# flagship shapes: transformer-base NMT (BASELINE.json config 3) at
# batch 64, S=256, d_model 512, H=8, vocab 30k
_B, _S, _D, _H, _V = 64, 256, 512, 8, 30000

CASES = [
    # (op, inputs builder, attrs, grad?, output index to time)
    ("scaled_dot_product_attention",
     lambda rs: {"Q": rs.rand(_B, _H, _S, _D // _H).astype("float32"),
                 "K": rs.rand(_B, _H, _S, _D // _H).astype("float32"),
                 "V": rs.rand(_B, _H, _S, _D // _H).astype("float32")},
     {"causal": True}, True),
    # the IN-MODEL condition of the round-4 +12% winner: bf16
    # operands + dropout (single-k-block kernels, in-kernel PRNG).
    # The f32/no-dropout row above is kept as the honest contrast —
    # the kernel LOSES there and the mix demotion logic must see both.
    ("scaled_dot_product_attention",
     lambda rs: {"Q": rs.rand(_B, _H, _S, _D // _H).astype(_BF16),
                 "K": rs.rand(_B, _H, _S, _D // _H).astype(_BF16),
                 "V": rs.rand(_B, _H, _S, _D // _H).astype(_BF16)},
     {"causal": True, "dropout_rate": 0.1}, True, 0,
     "sdpa[bf16+dropout]"),
    ("layer_norm",
     lambda rs: {"X": rs.rand(_B * _S, _D).astype("float32"),
                 "Scale": rs.rand(_D).astype("float32"),
                 "Bias": rs.rand(_D).astype("float32")},
     {"begin_norm_axis": 1}, True),
    # out_index 1 = Loss: timing Softmax (index 0) would let XLA
    # dead-code the cross-entropy path this kernel targets
    ("softmax_with_cross_entropy",
     lambda rs: {"Logits": rs.rand(_B * _S, _V).astype("float32"),
                 "Label": rs.randint(0, _V, (_B * _S, 1))
                 .astype("int64")},
     {}, True, 1),
    ("fused_linear_xent",
     lambda rs: {"X": rs.rand(_B * _S, _D).astype("float32"),
                 "W": (rs.rand(_D, _V).astype("float32") * 0.02),
                 "Label": rs.randint(0, _V, (_B * _S, 1))
                 .astype("int64")},
     {"epsilon": 0.1}, True),
    ("adam",
     lambda rs: {"Param": rs.rand(_D, 4 * _D).astype("float32"),
                 "Grad": rs.rand(_D, 4 * _D).astype("float32"),
                 "Moment1": rs.rand(_D, 4 * _D).astype("float32"),
                 "Moment2": rs.rand(_D, 4 * _D).astype("float32"),
                 "LearningRate": np.asarray([1e-3], np.float32),
                 "Beta1Pow": np.asarray([0.9], np.float32),
                 "Beta2Pow": np.asarray([0.999], np.float32)},
     {}, False),
]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--iters", type=int, default=100)
    ap.add_argument("--only", help="comma-separated op subset")
    args = ap.parse_args(argv)

    from op_bench import bench_op

    def emit(r):
        # stream each row the moment it's measured: a wedged compile
        # (observed on-chip round 4: one bad variant hung the remote
        # compile helper 800s) then costs only the tail of the table,
        # never the rows already on stdout
        if args.json:
            print(json.dumps(r), flush=True)
        elif "error" in r:
            print("| %s | ERROR %s | | | |" % (r["op"], r["error"]),
                  flush=True)
        else:
            print("| %s | %.3f | %.3f | %.2fx | %s |"
                  % (r["op"], r["base_ms"], r["pallas_ms"],
                     r["speedup"], r["winner"]), flush=True)

    if not args.json:
        print("| op | base (XLA) ms | pallas ms | speedup | winner |")
        print("|---|---|---|---|---|")

    try:
        stall_s = float(os.environ.get("KERNEL_TABLE_STALL_S", 360))
    except (TypeError, ValueError):
        stall_s = 360.0

    rs = np.random.RandomState(0)
    only = set(args.only.split(",")) if args.only else None
    # the 30k-vocab cases run ~10-40 ms/step — cap their in-graph
    # iters so each timed dispatch stays under a few seconds (an
    # explicit smaller --iters is still honored)
    heavy_cap = {"softmax_with_cross_entropy": 30,
                 "fused_linear_xent": 30}
    per_op_iters = {op: min(args.iters, cap)
                    for op, cap in heavy_cap.items()}
    for case in CASES:
        op, mk, attrs, grad = case[:4]
        out_index = case[4] if len(case) > 4 else 0
        label = case[5] if len(case) > 5 else op
        if only and op not in only and label not in only:
            continue

        def stalled(op=label):
            emit({"op": op, "error": "stalled >%.0fs (wedged compile?)"
                  % stall_s})
            os._exit(2)

        guard = threading.Timer(stall_s, stalled)
        guard.daemon = True
        guard.start()
        try:
            results = bench_op(op, mk(rs), attrs,
                               iters=per_op_iters.get(op, args.iters),
                               grad=grad, out_index=out_index)
        except Exception as e:  # keep the table going per-op
            emit({"op": label, "error": repr(e)})
            continue
        finally:
            guard.cancel()
        by_lib = {r["library"]: r for r in results}
        base = by_lib.get("base")
        pallas = by_lib.get("pallas")
        if not base or not pallas:
            emit({"op": label, "error": "missing variant: %s"
                  % sorted(by_lib)})
            continue
        b_ms = base["us_per_call"] / 1e3
        p_ms = pallas["us_per_call"] / 1e3
        speedup = b_ms / p_ms if p_ms else 0.0
        emit({"op": label, "base_ms": round(b_ms, 3),
              "pallas_ms": round(p_ms, 3),
              "speedup": round(speedup, 3),
              "winner": "pallas" if speedup > 1.0 else "xla"})


if __name__ == "__main__":
    main()
