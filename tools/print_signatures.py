"""Dump every public API signature, one line each — the analog of the
reference's tools/print_signatures.py, whose output is frozen in
paddle/fluid/API.spec (599 entries) and diffed by CI (tools/diff_api.py)
so the public surface can't change silently.

Regenerate after an intentional API change:

    python tools/print_signatures.py > API.spec
"""

from __future__ import annotations

import inspect
import os
import sys
import types

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# Public modules whose surface is frozen. Submodules re-exported from
# `layers` are covered through the `layers` namespace itself.
PUBLIC_MODULES = [
    "paddle_tpu",
    "paddle_tpu.layers",
    "paddle_tpu.optimizer",
    "paddle_tpu.average",
    "paddle_tpu.backward",
    "paddle_tpu.io",
    "paddle_tpu.metrics",
    "paddle_tpu.nets",
    "paddle_tpu.clip",
    "paddle_tpu.regularizer",
    "paddle_tpu.initializer",
    "paddle_tpu.param_attr",
    "paddle_tpu.profiler",
    "paddle_tpu.observability",
    "paddle_tpu.unique_name",
    "paddle_tpu.reader",
    "paddle_tpu.dygraph",
    "paddle_tpu.parallel",
    "paddle_tpu.transpiler",
    "paddle_tpu.contrib",
    "paddle_tpu.contrib.mixed_precision",
    "paddle_tpu.contrib.slim.nas",
    "paddle_tpu.contrib.slim.quantization",
    "paddle_tpu.contrib.utils",
    "paddle_tpu.recordio",
    "paddle_tpu.resilience",
    "paddle_tpu.chaos",
    "paddle_tpu.compile_cache",
    "paddle_tpu.analysis",
    "paddle_tpu.distributed",
    "paddle_tpu.serving",
    "paddle_tpu.engine",
    "paddle_tpu.dataset_factory",
    "paddle_tpu.incubate.data_generator",
    "paddle_tpu.incubate.fleet.base.role_maker",
    "paddle_tpu.incubate.fleet.collective",
    "paddle_tpu.incubate.fleet.parameter_server",
]


def _sig(obj):
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"


def _entries_for(modname):
    __import__(modname)
    mod = sys.modules[modname]
    entries = []
    # a module that declares __all__ freezes exactly that surface;
    # otherwise every public paddle_tpu-defined callable is frozen
    # (accidental convenience imports would otherwise become API)
    public = getattr(mod, "__all__", None)
    for name in sorted(public if public is not None else dir(mod)):
        if name.startswith("_"):
            continue
        obj = getattr(mod, name)
        qual = "%s.%s" % (modname, name)
        if isinstance(obj, types.ModuleType):
            continue
        if inspect.isclass(obj):
            if obj.__module__ and not obj.__module__.startswith(
                    "paddle_tpu"):
                continue
            entries.append("%s %s" % (qual, _sig(obj.__init__)))
            for mname in sorted(dir(obj)):
                if mname.startswith("_"):
                    continue
                m = inspect.getattr_static(obj, mname)
                if isinstance(m, (staticmethod, classmethod)):
                    m = m.__func__
                if inspect.isfunction(m):
                    entries.append("%s.%s %s" % (qual, mname, _sig(m)))
        elif callable(obj):
            if getattr(obj, "__module__", "") and \
                    not obj.__module__.startswith("paddle_tpu"):
                continue
            entries.append("%s %s" % (qual, _sig(obj)))
    return entries


def generate():
    lines = []
    for modname in PUBLIC_MODULES:
        lines.extend(_entries_for(modname))
    return lines


if __name__ == "__main__":
    for line in generate():
        print(line)
