"""Compile-only HBM estimate for a train-step at a given batch size.

Safety tool for the tunneled backend: a RESOURCE_EXHAUSTED *launch*
leaks server-side buffers (BASELINE.md round-4 harness learnings), so
batch-size scaling is decided by asking the compiler for the peak
allocation instead of probing with a real step.

    python tools/mem_estimate.py resnet50 64 96 128
    python tools/mem_estimate.py transformer 64 96

Prints one JSON line per batch with the compiler's memory_analysis
(no step is ever launched; only the startup program runs, which
allocates just the parameters).
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_default_prng_impl", "rbg")
jax.config.update("jax_compilation_cache_dir", os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    ".jax_cache"))

import numpy as np  # noqa: E402


def _build(model, batch):
    import paddle_tpu as fluid
    from paddle_tpu.contrib import mixed_precision as amp

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 1
    rs = np.random.RandomState(0)
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            if model == "resnet50":
                from paddle_tpu.models import resnet as R
                img = fluid.layers.data("img", shape=[3, 224, 224],
                                        dtype="float32")
                label = fluid.layers.data("label", shape=[1],
                                          dtype="int64")
                pred = R.resnet50(img)
                loss, _ = R.loss_and_acc(pred, label)
                opt = amp.decorate(
                    fluid.optimizer.MomentumOptimizer(0.1, 0.9))
                opt.minimize(loss)
                feed = {"img": rs.rand(batch, 3, 224, 224)
                        .astype(np.float32),
                        "label": rs.randint(0, 1000, (batch, 1))
                        .astype(np.int64)}
            elif model == "transformer":
                from paddle_tpu.models import transformer as T
                cfg = T.TransformerConfig(
                    src_vocab=30000, tgt_vocab=30000, max_len=256,
                    d_model=512, d_ffn=2048, n_head=8, n_layer=6,
                    dropout=0.1)
                loss, _tok, _ = T.transformer(cfg)
                opt = amp.decorate(fluid.optimizer.AdamOptimizer(1e-3))
                opt.minimize(loss)
                feed = T.make_fake_batch(cfg, batch)
            else:
                raise SystemExit("unknown model %r" % model)
    return main, startup, loss, feed


def estimate(model, batch):
    import paddle_tpu as fluid
    from paddle_tpu.executor import run_block

    main, startup, loss, feed = _build(model, batch)
    scope = fluid.core.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)           # params only — safe allocation
        block = main.global_block()
        persist = {n: scope.find_var(n) for n, v in block.vars.items()
                   if v.persistable and scope.has_var(n)
                   and scope.find_var(n) is not None}
        feed_dev = {k: jax.numpy.asarray(v) for k, v in feed.items()}

        def step(persist_vals, feed_vals, key):
            env = dict(persist_vals)
            env.update(feed_vals)
            run_block(block, env, key)
            return ({n: env[n] for n in persist_vals},
                    env[loss.name])

        key = jax.random.key(0)
        lowered = jax.jit(step, donate_argnums=(0,)).lower(
            persist, feed_dev, key)
        compiled = lowered.compile()
        ma = compiled.memory_analysis()
        row = {"model": model, "batch": batch}
        for field in ("temp_size_in_bytes", "argument_size_in_bytes",
                      "output_size_in_bytes",
                      "alias_size_in_bytes",
                      "peak_memory_in_bytes"):
            v = getattr(ma, field, None)
            if v is not None:
                row[field.replace("_in_bytes", "_gb")] = round(
                    v / 2**30, 3)
        return row


def main():
    args = sys.argv[1:]
    if not args:
        raise SystemExit(__doc__)
    model, batches = args[0], [int(b) for b in args[1:]] or [64]
    for b in batches:
        try:
            row = estimate(model, b)
        except Exception as e:  # noqa: BLE001
            row = {"model": model, "batch": b,
                   "error": repr(e)[:300]}
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
