"""Finite-difference check of the flash attention in-kernel dropout
gradients at f32 (the round-4 review repro: fwd/bwd grid groupings
must agree for the regenerated PRNG masks to match — _pick_G).
Run on the real chip; CPU interpret mode cannot emulate the TPU PRNG.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    ".jax_cache"))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from paddle_tpu.ops.pallas import attention as A  # noqa: E402


def main():
    print("backend:", jax.default_backend(), flush=True)
    rs = np.random.RandomState(0)
    B, H, Sq, Sk, Dh = 2, 8, 16, 128, 64
    q = jnp.asarray(rs.randn(B, H, Sq, Dh).astype(np.float32))
    k = jnp.asarray(rs.randn(B, H, Sk, Dh).astype(np.float32))
    v = jnp.asarray(rs.randn(B, H, Sk, Dh).astype(np.float32))
    seed = jnp.float32(5)
    rate = 0.5

    @jax.jit
    def loss(v_):
        return jnp.sum(A._sdpa_flash(q, k, v_, None, seed, 0.125,
                                     rate, False) ** 2)

    g = jax.jit(jax.grad(loss))(v)
    print("grad computed", flush=True)
    bad = 0
    for h in range(H):
        i = (1, h, 7, 3)
        eps = 1e-2
        fd = (loss(v.at[i].add(eps))
              - loss(v.at[i].add(-eps))) / (2 * eps)
        diff = abs(float(fd) - float(g[i]))
        ok = diff < 0.02
        bad += not ok
        print("head %d fd %.4f grad %.4f %s"
              % (h, float(fd), float(g[i]), "ok" if ok else "BAD"),
              flush=True)
    print("FD_CHECK", "PASS" if bad == 0 else "FAIL(%d)" % bad,
          flush=True)
    return 0 if bad == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
