#!/usr/bin/env python
"""AST lock-order lint: find lock-ordering cycles and telemetry emits
under held non-reentrant locks, statically.

The PR 11 ``_SINGLETON_MU`` deadlock (an accessor re-acquiring the
non-reentrant singleton lock it was called under) is a CLASS of bug,
not an incident: any two locks acquired in opposite orders on two
threads, or any non-reentrant lock re-entered through a call chain,
wedges the process with no exception to observe. This lint makes the
class a standing check over the threaded packages
(``observability/``, ``serving/``, ``distributed/`` by default):

  1. discover locks — module-level ``NAME = threading.Lock()`` /
     ``RLock()`` / ``Condition()`` and instance attrs
     ``self.attr = threading.Lock()`` (identity: module.Class.attr —
     one id per DECLARATION, the granularity ordering is about);
  2. build per-function acquisition records: ``with lock:`` nesting
     plus ``lock.acquire()`` events, and the calls made while holding;
  3. propagate transitively (fixpoint over the intra-package call
     graph: ``self.method()``, module functions, imported modules);
  4. report (a) ordering CYCLES (A→B and B→A reachable), (b) SELF
     re-entry of a non-reentrant lock through any call chain, and
     (c) journal/registry emits (``emit(...)``, ``registry(...)``)
     reached while a non-reentrant lock is held — the emit path takes
     the telemetry plane's own locks and may call arbitrary sinks, so
     it must never run under a hot-path mutex.

Deliberately conservative where resolution fails (unknown callee or
lock expression ⇒ no claim); suppress a justified single site with a
``# lock-lint: ok`` comment on the acquiring/calling line.

Exit code 1 when violations are found (CI gate), 0 otherwise.

    python tools/lock_lint.py                   # default packages
    python tools/lock_lint.py --json paddle_tpu/serving
"""

from __future__ import annotations

import argparse
import ast
import collections
import json
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATHS = (
    "paddle_tpu/observability",
    "paddle_tpu/serving",
    "paddle_tpu/distributed",
    # reshard.py rides the directory above, but the live-cutover
    # protocol is exactly the code this gate exists for (journal
    # emits must never happen under the migration lock), so it is
    # pinned EXPLICITLY: a future split of distributed/ into
    # subpackages cannot silently drop it from the scan
    "paddle_tpu/distributed/reshard.py",
    # sparse.py rides paddle_tpu/serving above, but its per-request
    # tier pipeline holds the cache mutex on the serving HOT PATH
    # (journal emits are collected under the lock and flushed after
    # release — docs/serving.md §Sparse serving), so it is pinned
    # EXPLICITLY for the same reason as reshard.py: no future package
    # split may silently drop it from the scan
    "paddle_tpu/serving/sparse.py",
    "paddle_tpu/engine",
    # the fault-point plane fires INSIDE protocol handlers that hold
    # the server mutex (ps.py _mu, sparse shard locks): faultpoint()
    # must queue its journal twin under its own registry lock and
    # flush only from flush_events() — an emit under a held hot-path
    # lock here would deadlock the very crash drills the plane exists
    # to run, so the package is pinned EXPLICITLY
    "paddle_tpu/chaos",
    # engine/pipeline.py rides paddle_tpu/engine above, but the
    # microbatch schedule it traces IS the step hot path (every
    # pipelined training step runs through it), so it is pinned
    # EXPLICITLY like reshard.py/sparse.py: a future split of
    # engine/ cannot silently drop the scheduler from the scan
    "paddle_tpu/engine/pipeline.py",
)

# mutexes only: semaphores are deliberately NOT tracked — the repo
# uses them as cross-thread completion SIGNALS (Semaphore(0) with
# release() on another thread), where "held between acquire and
# release" is not a meaningful region and ordering edges are noise
_LOCK_CTORS = {"Lock": False, "RLock": True, "Condition": True}
# telemetry entry points that must not run under a held hot-path lock
_EMIT_NAMES = {"emit"}
_REGISTRY_NAMES = {"registry"}
PRAGMA = "lock-lint: ok"


class Lock:
    __slots__ = ("key", "reentrant", "file", "line")

    def __init__(self, key, reentrant, file, line):
        self.key = key          # "module.NAME" or "module.Class.attr"
        self.reentrant = reentrant
        self.file = file
        self.line = line


class FuncInfo:
    """Per-function record of lock events and outgoing calls."""

    __slots__ = ("key", "file", "acquires", "calls", "emits")

    def __init__(self, key, file):
        self.key = key
        self.file = file
        # (lock_key, line, held_tuple, pragma_ok)
        self.acquires: List[Tuple] = []
        # (callee_key_or_None, call_display, line, held_tuple,
        #  pragma_ok, is_emit, is_registry)
        self.calls: List[Tuple] = []
        self.emits: List[Tuple] = []


def _module_name(path: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), REPO)
    if rel.startswith(".."):
        # outside the repo (test fixtures): absolute path as the id
        rel = os.path.abspath(path).lstrip(os.sep)
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _lock_ctor(node) -> Optional[bool]:
    """Is this expression a threading lock constructor? Returns its
    reentrancy, or None."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    name = None
    if isinstance(f, ast.Attribute) and \
            isinstance(f.value, ast.Name) and \
            f.value.id == "threading":
        name = f.attr
    elif isinstance(f, ast.Name):
        name = f.id
    if name in _LOCK_CTORS:
        return _LOCK_CTORS[name]
    return None


class _ModuleScan(ast.NodeVisitor):
    """One pass over a module: lock declarations, import aliases, and
    per-function event records."""

    def __init__(self, mod: str, file: str, src_lines: List[str]):
        self.mod = mod
        self.file = file
        self.lines = src_lines
        self.locks: Dict[str, Lock] = {}
        self.funcs: Dict[str, FuncInfo] = {}
        self.import_mods: Dict[str, str] = {}   # alias -> module
        self.import_names: Dict[str, Tuple[str, str]] = {}
        self.class_names: set = set()
        self._class: List[str] = []
        self._func: List[FuncInfo] = []
        self._held: List[str] = []

    # -- declarations -------------------------------------------------------
    def visit_Import(self, node):
        for a in node.names:
            self.import_mods[a.asname or a.name.split(".")[0]] = a.name

    def visit_ImportFrom(self, node):
        if node.level:
            base = self.mod.split(".")
            # relative import: level N strips N trailing components
            # (module's own name counts as one)
            base = base[: len(base) - node.level]
            mod = ".".join(base + ([node.module] if node.module
                                   else []))
        else:
            mod = node.module or ""
        for a in node.names:
            self.import_names[a.asname or a.name] = (mod, a.name)

    def visit_Assign(self, node):
        re = _lock_ctor(node.value)
        if re is not None:
            for t in node.targets:
                key = None
                if isinstance(t, ast.Name):
                    if self._class and not self._func:
                        # class-body attribute (the _SINGLETON_MU
                        # shape as a class attr): same key space as
                        # self.attr assignments so both spellings
                        # resolve to ONE lock
                        key = "%s.%s.%s" % (self.mod,
                                            self._class[-1], t.id)
                    elif not self._class:
                        key = "%s.%s" % (self.mod, t.id)
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self" and self._class:
                    key = "%s.%s.%s" % (self.mod, self._class[-1],
                                        t.attr)
                if key:
                    self.locks[key] = Lock(key, re, self.file,
                                           node.lineno)
        self.generic_visit(node)

    # -- structure ----------------------------------------------------------
    def visit_ClassDef(self, node):
        self.class_names.add(node.name)
        self._class.append(node.name)
        self.generic_visit(node)
        self._class.pop()

    def _fn(self, node):
        qual = ".".join(self._class + [node.name])
        info = FuncInfo("%s.%s" % (self.mod, qual), self.file)
        self.funcs[info.key] = info
        self._func.append(info)
        held, self._held = self._held, []
        self.generic_visit(node)
        self._held = held
        self._func.pop()

    visit_FunctionDef = _fn
    visit_AsyncFunctionDef = _fn

    # -- lock expression resolution ----------------------------------------
    def _lock_of(self, expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            key = "%s.%s" % (self.mod, expr.id)
            return key if key in self.locks else None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base = expr.value.id
            if base == "self" and self._class:
                key = "%s.%s.%s" % (self.mod, self._class[-1],
                                    expr.attr)
                return key if key in self.locks else None
            if base in self.class_names:
                # ClassName._MU spelling of a class-attribute lock
                key = "%s.%s.%s" % (self.mod, base, expr.attr)
                return key if key in self.locks else None
        return None

    def _pragma(self, line: int) -> bool:
        try:
            return PRAGMA in self.lines[line - 1]
        except IndexError:
            return False

    # -- events -------------------------------------------------------------
    def visit_With(self, node):
        acquired = []
        for item in node.items:
            lk = self._lock_of(item.context_expr)
            if lk is not None and self._func:
                self._func[-1].acquires.append(
                    (lk, node.lineno, tuple(self._held),
                     self._pragma(node.lineno)))
                self._held.append(lk)
                acquired.append(lk)
        for stmt in node.body:
            self.visit(stmt)
        for lk in reversed(acquired):
            # remove THIS with's instances specifically: a manual
            # lock.acquire() inside the body may have appended since
            self._unhold(lk)
        # with-items' own expressions (callables etc.)
        for item in node.items:
            self.visit(item.context_expr)

    def _unhold(self, lk):
        for i in range(len(self._held) - 1, -1, -1):
            if self._held[i] == lk:
                del self._held[i]
                return

    visit_AsyncWith = visit_With

    def _callee_key(self, f) -> Tuple[Optional[str], str]:
        """Resolve a call target to a scanned-function key (or None)
        plus a display string."""
        if isinstance(f, ast.Name):
            name = f.id
            if name in self.import_names:
                mod, orig = self.import_names[name]
                return "%s.%s" % (mod, orig), name
            return "%s.%s" % (self.mod, name), name
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name):
                base = f.value.id
                if base == "self" and self._class:
                    return ("%s.%s.%s" % (self.mod, self._class[-1],
                                          f.attr),
                            "self.%s" % f.attr)
                if base in self.import_mods:
                    return ("%s.%s" % (self.import_mods[base], f.attr),
                            "%s.%s" % (base, f.attr))
                if base in self.import_names:
                    mod, orig = self.import_names[base]
                    return ("%s.%s.%s" % (mod, orig, f.attr),
                            "%s.%s" % (base, f.attr))
            return None, ast.unparse(f) if hasattr(ast, "unparse") \
                else f.attr
        return None, "<dynamic>"

    def visit_Call(self, node):
        if self._func:
            info = self._func[-1]
            f = node.func
            # lock.acquire() opens a HELD region lasting until a
            # matching release() or the end of the function
            # (conservative: a conditional acquire over-approximates)
            if isinstance(f, ast.Attribute) and f.attr == "acquire":
                lk = self._lock_of(f.value)
                if lk is not None:
                    info.acquires.append(
                        (lk, node.lineno, tuple(self._held),
                         self._pragma(node.lineno)))
                    self._held.append(lk)
            elif isinstance(f, ast.Attribute) and f.attr == "release":
                lk = self._lock_of(f.value)
                if lk is not None:
                    self._unhold(lk)
            key, disp = self._callee_key(f)
            leaf = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            is_emit = leaf in _EMIT_NAMES
            is_reg = leaf in _REGISTRY_NAMES
            info.calls.append((key, disp, node.lineno,
                               tuple(self._held),
                               self._pragma(node.lineno),
                               is_emit, is_reg))
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# analysis over the scanned set
# ---------------------------------------------------------------------------

def scan(paths) -> Tuple[Dict[str, Lock], Dict[str, FuncInfo]]:
    locks: Dict[str, Lock] = {}
    funcs: Dict[str, FuncInfo] = {}
    for root in paths:
        root = os.path.join(REPO, root) if not os.path.isabs(root) \
            else root
        files = []
        if os.path.isfile(root):
            files = [root]
        else:
            for d, _dirs, names in os.walk(root):
                files += [os.path.join(d, n) for n in names
                          if n.endswith(".py")]
        if not files:
            # a typo'd/renamed path must fail LOUDLY: a vacuous scan
            # exiting 0 would turn the CI gate into a no-op
            raise FileNotFoundError(
                "lock_lint: no Python files under %r — check the "
                "scan path" % root)
        for path in sorted(files):
            with open(path) as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
            s = _ModuleScan(_module_name(path), path,
                            src.splitlines())
            s.visit(tree)
            locks.update(s.locks)
            funcs.update(s.funcs)
    return locks, funcs


def _transitive_acquires(funcs) -> Dict[str, Set[str]]:
    """Fixpoint: every lock a function may acquire, directly or
    through calls into scanned functions."""
    acq = {k: {a[0] for a in f.acquires} for k, f in funcs.items()}
    changed = True
    while changed:
        changed = False
        for k, f in funcs.items():
            for callee, _d, _l, _h, _p, _e, _r in f.calls:
                extra = acq.get(callee)
                if extra and not extra <= acq[k]:
                    acq[k] |= extra
                    changed = True
    return acq


def _emits_transitively(funcs) -> Dict[str, bool]:
    em = {k: any(c[5] or c[6] for c in f.calls)
          for k, f in funcs.items()}
    changed = True
    while changed:
        changed = False
        for k, f in funcs.items():
            if em[k]:
                continue
            if any(em.get(c[0]) for c in f.calls):
                em[k] = True
                changed = True
    return em


def analyze(locks: Dict[str, Lock],
            funcs: Dict[str, FuncInfo]) -> dict:
    acq_star = _transitive_acquires(funcs)
    emit_star = _emits_transitively(funcs)

    edges: Dict[Tuple[str, str], List[dict]] = \
        collections.defaultdict(list)
    violations: List[dict] = []

    def note_edge(a, b, where):
        edges[(a, b)].append(where)

    for fk, f in funcs.items():
        for lk, line, held, ok in f.acquires:
            if ok:
                continue
            for h in held:
                if h == lk:
                    if not locks[lk].reentrant:
                        violations.append({
                            "kind": "self_deadlock",
                            "lock": lk, "func": fk,
                            "file": f.file, "line": line,
                            "detail": "non-reentrant lock %r "
                            "re-acquired while already held in the "
                            "same function" % lk})
                else:
                    note_edge(h, lk, {"func": fk, "file": f.file,
                                      "line": line, "via": "with"})
        for callee, disp, line, held, ok, _e, _r in f.calls:
            if ok or not held or callee not in acq_star:
                continue
            for lk in acq_star[callee]:
                for h in held:
                    if h == lk:
                        if not locks[lk].reentrant:
                            violations.append({
                                "kind": "self_deadlock",
                                "lock": lk, "func": fk,
                                "file": f.file, "line": line,
                                "detail": "call to %s() while "
                                "holding non-reentrant %r; the "
                                "callee (re)acquires it — the "
                                "_SINGLETON_MU class" % (disp, lk)})
                    else:
                        note_edge(h, lk,
                                  {"func": fk, "file": f.file,
                                   "line": line,
                                   "via": "call %s()" % disp})

    # emits under held non-reentrant locks
    for fk, f in funcs.items():
        for callee, disp, line, held, ok, is_emit, is_reg in f.calls:
            if ok:
                continue
            direct = is_emit or is_reg
            transitive = callee in emit_star and emit_star[callee]
            if not (direct or transitive):
                continue
            bad = [h for h in held if not locks[h].reentrant]
            # the telemetry plane's own modules emit under their own
            # locks by design (the journal's seq/sink critical
            # section IS the emit)
            if bad and not fk.startswith("paddle_tpu.observability."):
                violations.append({
                    "kind": "emit_under_lock",
                    "lock": bad[0], "func": fk,
                    "file": f.file, "line": line,
                    "detail": "%s() reached while holding "
                    "non-reentrant %r — journal/registry emits take "
                    "the telemetry plane's locks and run sink I/O; "
                    "move the emit outside the critical section"
                    % (disp, bad[0])})

    # ordering cycles over the edge graph
    graph: Dict[str, Set[str]] = collections.defaultdict(set)
    for (a, b) in edges:
        graph[a].add(b)
    for cyc in _find_cycles(graph):
        witness = []
        for a, b in zip(cyc, cyc[1:] + cyc[:1]):
            w = edges.get((a, b))
            if w:
                witness.append(dict(w[0], edge="%s -> %s" % (a, b)))
        violations.append({
            "kind": "cycle",
            "locks": cyc,
            "detail": "lock-order cycle: %s -> %s — two threads "
            "taking these in opposite orders deadlock"
            % (" -> ".join(cyc), cyc[0]),
            "witness": witness})

    return {
        "locks": sorted(locks),
        "functions_scanned": len(funcs),
        "edges": [{"from": a, "to": b, "sites": w[:3]}
                  for (a, b), w in sorted(edges.items())],
        "violations": violations,
    }


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles via DFS with canonicalization (small graphs:
    a handful of locks per package)."""
    cycles: Set[Tuple[str, ...]] = set()

    def dfs(start, node, path, seen):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) > 0:
                cyc = path[:]
                i = cyc.index(min(cyc))
                cycles.add(tuple(cyc[i:] + cyc[:i]))
            elif nxt not in seen and nxt > start:
                # only explore nodes > start: each cycle found once,
                # rooted at its smallest member
                dfs(start, nxt, path + [nxt], seen | {nxt})

    for n in sorted(graph):
        dfs(n, n, [n], {n})
    return [list(c) for c in sorted(cycles)]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                    help="packages/files to scan (default: %s)"
                    % ", ".join(DEFAULT_PATHS))
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    try:
        locks, funcs = scan(args.paths or DEFAULT_PATHS)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    report = analyze(locks, funcs)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print("lock_lint: %d lock(s), %d function(s), %d ordering "
              "edge(s), %d violation(s)"
              % (len(report["locks"]), report["functions_scanned"],
                 len(report["edges"]), len(report["violations"])))
        for v in report["violations"]:
            loc = "%s:%s" % (v.get("file"), v.get("line")) \
                if v.get("file") else ""
            print("  [%s] %s %s" % (v["kind"], loc, v["detail"]))
    return 1 if report["violations"] else 0


if __name__ == "__main__":
    sys.exit(main())
