#!/bin/bash
# Round-5 chip-window capture. Waits for the axon tunnel (claims
# BLOCK rather than fail; killed claims leave stale leases, so probes
# get long timeouts and 300s cool-downs), then captures the round-5
# evidence set in priority order, flushing the log after every step so
# a mid-capture outage still leaves artifacts:
#   1. bench.py               (headline: flash mix, the 0.4215 re-capture)
#   2. tools/lever_ab.py fast (baseline + FINAL config, +12% witness)
#   3. bench.py --all         (5-config table, regenerated clean)
#   4. tools/kernel_table.py  (refer-vs-pallas win table)
#   5. tools/mem_estimate.py resnet50 96 128 (compile-only, batch lever)
# Raw stdout is the artifact: curate into docs/bench_evidence_r5/ and
# commit. Touch $STOP_FILE to stop (ALWAYS do this well before round
# end — do not race the driver's claim).
set -u
LOG="${1:-/root/repo/.window_capture_r5.log}"
STOP_FILE="/root/repo/.stop_prober"
MAX_HOURS="${MAX_HOURS:-8}"
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))
cd /root/repo

say() { echo "[capture $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    [ -e "$STOP_FILE" ] && { say "stop file present — exiting"; exit 3; }
    say "probing for a claim (timeout 900s)..."
    if timeout 900 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
x = jnp.ones((512, 512), jnp.bfloat16)
(x @ x).sum().block_until_ready()
print('CLAIM_OK', d.device_kind)
" >>"$LOG" 2>&1 && tail -5 "$LOG" | grep -q CLAIM_OK; then
        say "window open — bench headline (flash mix)"
        timeout 2400 python bench.py >>"$LOG" 2>&1
        say "lever_ab FULL (r5: mxu_ln_grad rows)"
        timeout 3600 python tools/lever_ab.py >>"$LOG" 2>&1
        say "bench --all (longseq + resnet s2d A/B rows)"
        timeout 4800 python bench.py --all >>"$LOG" 2>&1
        say "kernel table (incl. bf16+dropout sdpa row)"
        KERNEL_TABLE_STALL_S=360 timeout 3000 \
            python tools/kernel_table.py --json >>"$LOG" 2>&1
        say "resnet mem estimates 96/128"
        timeout 2400 python tools/mem_estimate.py resnet50 96 128 \
            >>"$LOG" 2>&1
        say "resnet b96 (only if mem_estimate said it fits: the"
        say "  runner itself re-checks and skips on estimate-fail)"
        timeout 2400 python tools/resnet_batch_probe.py 96 \
            >>"$LOG" 2>&1
        say "step anatomy profile (copies chase, VERDICT r4 #8)"
        timeout 1800 python tools/profile_step.py >>"$LOG" 2>&1
        say "capture complete"
        exit 0
    fi
    say "no claim — cooling down 300s (stale-lease expiry)"
    sleep 300
done
say "deadline reached without a window"
exit 3
