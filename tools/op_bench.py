"""Per-op micro-benchmark — the analog of the reference's
paddle/fluid/operators/benchmark/op_tester.cc (time one op from a
config) and operators/jit/benchmark.cc (compare implementations and
report the best).

Times a single op through the real executor, once per registered
library variant (base XLA lowering vs pallas kernels), and prints one
JSON line per variant plus the winner:

    python tools/op_bench.py matmul --inputs X=256x256,Y=256x256
    python tools/op_bench.py softmax --inputs X=512x512 --grad
    python tools/op_bench.py --list          # ops with variants
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def parse_inputs(spec):
    """"X=2x3,Y=3x4" or "X=2x3:int64" → {slot: ndarray}."""
    out = {}
    if not spec:
        return out
    rs = np.random.RandomState(0)
    for part in spec.split(","):
        slot, shape = part.split("=")
        dtype = "float32"
        if ":" in shape:
            shape, dtype = shape.split(":")
        dims = tuple(int(d) for d in shape.split("x"))
        if np.issubdtype(np.dtype(dtype), np.integer):
            out[slot] = rs.randint(0, 8, dims).astype(dtype)
        else:
            out[slot] = rs.rand(*dims).astype(dtype)
    return out


def parse_attrs(spec):
    out = {}
    if not spec:
        return out
    for part in spec.split(","):
        k, v = part.split("=")
        for cast in (int, float):
            try:
                out[k] = cast(v)
                break
            except ValueError:
                continue
        else:
            out[k] = {"True": True, "False": False}.get(v, v)
    return out


def _build_timed_program(op_type, np_inputs, attrs, grad, out_index):
    """One-op program shaped for honest in-graph repetition.

    The timing loop lives ON-DEVICE (Executor.run_repeated lax.scan —
    per-dispatch timing through a remote PJRT tunnel measures handle
    RTT, not the op). Inside a scan two compiler hazards would void
    the measurement, both defeated by a persistable f32[1] accumulator
    ``bench_acc``:

    - loop-invariant hoisting: identical inputs per step let XLA lift
      the op out of the loop. The first float input is perturbed by
      ``acc * 1e-30`` (bit-identical in f32, but data-dependent).
    - dead-code elimination: only the LAST step's fetches leave the
      scan, so unconsumed per-step outputs die. The op's timed output
      and every input gradient are reduced and folded into
      ``acc += total * 1e-30``, which each step carries forward.
    """
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu import ops as registry

    main = fluid.Program()
    with fluid.program_guard(main):
        block = main.global_block()
        acc = block.create_var(name="bench_acc", shape=[1],
                               dtype="float32", persistable=True)
        feed, op_inputs, grad_roots = {}, {}, []
        perturbed = False
        for slot, val in np_inputs.items():
            if isinstance(val, (list, tuple)):
                raise NotImplementedError(
                    "variadic input slots are not supported by the "
                    "timed builder")
            name = slot.lower()
            var = layers.data(name, shape=list(val.shape),
                              append_batch_size=False,
                              dtype=str(val.dtype))
            is_float = np.issubdtype(val.dtype, np.floating)
            var.stop_gradient = not is_float
            feed[name] = val
            use = var
            if not perturbed and is_float:
                use = layers.elementwise_add(
                    var, layers.scale(acc, scale=1e-30))
                perturbed = True
            if is_float:
                grad_roots.append(var)
            op_inputs[slot] = [use]
        if not perturbed:
            print("WARNING: %s has no float input to perturb — the "
                  "scan's anti-hoisting defense does not apply and "
                  "XLA may lift the op out of the timed loop"
                  % op_type, file=sys.stderr)
        opdef = registry.get(op_type)
        out_vars, op_outputs = [], {}
        for slot in opdef.output_slots:
            variadic = slot.endswith("*")
            sname = slot[:-1] if variadic else slot
            vs = [block.create_var(
                name="out_%s_0" % sname.lower(), shape=(),
                dtype="float32")]
            op_outputs[sname] = vs
            out_vars.extend(vs)
        block.append_op(type=op_type, inputs=op_inputs,
                        outputs=op_outputs, attrs=attrs or {})
        total = layers.reduce_sum(out_vars[out_index])
        if grad:
            gs = fluid.gradients(total, grad_roots)
            for g in gs:
                if g is not None:
                    total = layers.elementwise_add(
                        total, layers.reduce_sum(g))
        upd = layers.elementwise_add(
            acc, layers.scale(layers.reshape(total, [1]),
                              scale=1e-30))
        block.append_op(type="assign", inputs={"X": [upd]},
                        outputs={"Out": [acc]})
    return main, feed, acc


def _null_overhead_s(iters):
    """Constant dispatch+readback cost subtracted from every op
    timing. Delegates to the canonical measurer in bench.py
    (_dispatch_overhead_s — one null-scan protocol, maintained in one
    place); the null step itself is ~µs, so the overhead is
    iters-independent."""
    del iters
    from bench import _dispatch_overhead_s
    return _dispatch_overhead_s()


def bench_op(op_type, np_inputs, attrs, iters=100, warmup=None,
             grad=False, out_index=0, stage=True):
    """Time one op per registered library variant: `iters` in-graph
    steps per dispatch (run_repeated), two timed dispatches (best
    wins), null-overhead-corrected. `warmup` is accepted for API
    compatibility; the compile dispatch IS the warmup."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu import ops as registry

    opdef = registry.get(op_type)
    libraries = [None] + sorted(opdef.variants)
    null_s = _null_overhead_s(iters)
    results = []
    for lib in libraries:
        main, feed, acc = _build_timed_program(
            op_type, np_inputs, attrs, grad, out_index)
        if stage:
            # stage the feed on device ONCE — run_repeated's
            # jnp.asarray passes jax.Arrays through, so the timed
            # dispatch carries no host->device traffic
            feed = {k: jax.device_put(v) for k, v in feed.items()}
        exe = fluid.Executor()
        fluid.global_scope().set_var("bench_acc",
                                     np.zeros((1,), np.float32))
        run = lambda: exe.run_repeated(  # noqa: E731
            main, feed=feed, fetch_list=[acc], iters=iters,
            library=lib or "")
        out = run()                       # compile + warmup
        if not np.all(np.isfinite(np.asarray(out[0]))):
            raise FloatingPointError(
                "%s/%s produced non-finite accumulator"
                % (op_type, lib or "base"))
        best = None
        for _ in range(2):
            t0 = time.perf_counter()
            run()                         # returns after readback
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        # same correction policy as bench._timed_loop: when the null
        # overhead is >90% of the measurement (tiny ops on a fast
        # local backend), extrapolating through the subtraction is
        # meaningless — report uncorrected (conservative) instead of
        # a near-zero artifact
        corrected = best - null_s if null_s <= best * 0.9 else best
        us = max(corrected, 1e-9) / iters * 1e6
        results.append({
            "op": op_type, "library": lib or "base",
            "us_per_call": round(us, 2),
            "iters": iters, "grad": grad, "protocol": "scan",
            "overhead_ms": round(null_s * 1e3, 1),
            "inputs": {k: list(np.shape(v))
                       for k, v in np_inputs.items()},
        })
    best = min(results, key=lambda r: r["us_per_call"])
    for r in results:
        r["best"] = r["library"] == best["library"]
    return results


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("op", nargs="?", help="op type to benchmark")
    ap.add_argument("--inputs", default="", help="X=2x3,Y=3x4[:dtype]")
    ap.add_argument("--attrs", default="", help="k=v,k2=v2")
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--warmup", type=int, default=20)
    ap.add_argument("--grad", action="store_true",
                    help="include backward in the timed program")
    ap.add_argument("--list", action="store_true",
                    help="list ops that have library variants")
    args = ap.parse_args(argv)

    if args.list:
        from paddle_tpu import ops as registry
        for t in registry.all_op_types():
            v = registry.get(t).variants
            if v:
                print(t, "->", ", ".join(sorted(v)))
        return 0

    if not args.op:
        ap.error("op required (or --list)")
    results = bench_op(args.op, parse_inputs(args.inputs),
                       parse_attrs(args.attrs), iters=args.iters,
                       warmup=args.warmup, grad=args.grad)
    for r in results:
        print(json.dumps(r))
    return 0


if __name__ == "__main__":
    sys.exit(main())
