"""Per-op micro-benchmark — the analog of the reference's
paddle/fluid/operators/benchmark/op_tester.cc (time one op from a
config) and operators/jit/benchmark.cc (compare implementations and
report the best).

Times a single op through the real executor, once per registered
library variant (base XLA lowering vs pallas kernels), and prints one
JSON line per variant plus the winner:

    python tools/op_bench.py matmul --inputs X=256x256,Y=256x256
    python tools/op_bench.py softmax --inputs X=512x512 --grad
    python tools/op_bench.py --list          # ops with variants
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def parse_inputs(spec):
    """"X=2x3,Y=3x4" or "X=2x3:int64" → {slot: ndarray}."""
    out = {}
    if not spec:
        return out
    rs = np.random.RandomState(0)
    for part in spec.split(","):
        slot, shape = part.split("=")
        dtype = "float32"
        if ":" in shape:
            shape, dtype = shape.split(":")
        dims = tuple(int(d) for d in shape.split("x"))
        if np.issubdtype(np.dtype(dtype), np.integer):
            out[slot] = rs.randint(0, 8, dims).astype(dtype)
        else:
            out[slot] = rs.rand(*dims).astype(dtype)
    return out


def parse_attrs(spec):
    out = {}
    if not spec:
        return out
    for part in spec.split(","):
        k, v = part.split("=")
        for cast in (int, float):
            try:
                out[k] = cast(v)
                break
            except ValueError:
                continue
        else:
            out[k] = {"True": True, "False": False}.get(v, v)
    return out


def bench_op(op_type, np_inputs, attrs, iters=200, warmup=20,
             grad=False, out_index=0):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu import ops as registry
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tests"))
    from op_test import _build_op_program

    opdef = registry.get(op_type)
    libraries = [None] + sorted(opdef.variants)
    results = []
    for lib in libraries:
        main, feed, out_vars, in_map = _build_op_program(
            op_type, np_inputs, attrs)
        if grad:
            with fluid.program_guard(main):
                from paddle_tpu import layers
                loss = layers.reduce_sum(out_vars[out_index])
                fluid.gradients(loss, list(in_map.values()))
        exe = fluid.Executor()
        fetch = [out_vars[out_index]]

        def run():
            return exe.run(main, feed=feed, fetch_list=fetch,
                           return_numpy=False,
                           use_program_cache=True)

        # executor caches by (program, library) via FLAGS
        from paddle_tpu.core.flags import FLAGS
        old = FLAGS.op_library
        FLAGS.op_library = lib or ""
        try:
            out = None
            for _ in range(warmup):
                out = run()
            if out is not None:
                jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(iters):
                out = run()
            jax.block_until_ready(out)
            dt = time.perf_counter() - t0
        finally:
            FLAGS.op_library = old
        results.append({
            "op": op_type, "library": lib or "base",
            "us_per_call": round(dt / iters * 1e6, 2),
            "iters": iters, "grad": grad,
            "inputs": {k: list(np.shape(v))
                       for k, v in np_inputs.items()},
        })
    best = min(results, key=lambda r: r["us_per_call"])
    for r in results:
        r["best"] = r["library"] == best["library"]
    return results


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("op", nargs="?", help="op type to benchmark")
    ap.add_argument("--inputs", default="", help="X=2x3,Y=3x4[:dtype]")
    ap.add_argument("--attrs", default="", help="k=v,k2=v2")
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--warmup", type=int, default=20)
    ap.add_argument("--grad", action="store_true",
                    help="include backward in the timed program")
    ap.add_argument("--list", action="store_true",
                    help="list ops that have library variants")
    args = ap.parse_args(argv)

    if args.list:
        from paddle_tpu import ops as registry
        for t in registry.all_op_types():
            v = registry.get(t).variants
            if v:
                print(t, "->", ", ".join(sorted(v)))
        return 0

    if not args.op:
        ap.error("op required (or --list)")
    results = bench_op(args.op, parse_inputs(args.inputs),
                       parse_attrs(args.attrs), iters=args.iters,
                       warmup=args.warmup, grad=args.grad)
    for r in results:
        print(json.dumps(r))
    return 0


if __name__ == "__main__":
    sys.exit(main())
