#!/usr/bin/env python
"""Static program verifier CLI (the analysis plane's front door).

Load a saved program artifact — a ``save_inference_model`` directory
or its ``__model__`` file — and run the full static verifier over it
(IR invariant passes + rewrite contracts, paddle_tpu/analysis/): no
tracing, no XLA compile, findings printed with op/var citations.

Exit code: 0 when no error-severity findings, 2 otherwise (1 is
argparse/load failures) — so the CLI is a CI gate.

Examples
--------
    # verify a serialized model artifact
    python tools/verify_program.py path/to/model_dir
    python tools/verify_program.py path/to/model_dir/__model__ --json

    # sweep the static composition matrix
    # (guard x gradient_sync x pipelined x PS)
    python tools/verify_program.py --matrix --json

    # assume a gradient_sync mode and extra run-time feeds
    python tools/verify_program.py model_dir --gradient-sync q8 \\
        --feed lr --targets loss

``--emit-journal`` additionally emits one ``verifier_finding`` event
per finding into the configured journal (PADDLE_TPU_EVENT_JOURNAL),
so ``tools/doctor.py`` can cite program defects next to runtime
faults.
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_program(path):
    """(program, feed_names, target_names) from a model dir or a
    ``__model__`` file (the save_inference_model pickle desc)."""
    from paddle_tpu.framework import Program
    if os.path.isdir(path):
        path = os.path.join(path, "__model__")
    if not os.path.exists(path):
        raise FileNotFoundError("no program artifact at %r" % path)
    with open(path, "rb") as f:
        desc = pickle.load(f)
    program = Program.from_dict(desc["program"])
    return (program, list(desc.get("feed_names") or ()),
            list(desc.get("fetch_names") or ()))


class _Parser(argparse.ArgumentParser):
    """Usage failures exit 1, keeping 2 EXCLUSIVELY for 'the program
    has error-severity findings' — the code the CI gate keys on (a
    typo'd flag must not read as a verifier failure)."""

    def error(self, message):
        self.print_usage(sys.stderr)
        print("%s: error: %s" % (self.prog, message),
              file=sys.stderr)
        sys.exit(1)


def main(argv=None):
    ap = _Parser(description=__doc__)
    ap.add_argument("model", nargs="?", default=None,
                    help="save_inference_model dir or __model__ file")
    ap.add_argument("--matrix", action="store_true",
                    help="run the static composition-matrix sweep "
                    "instead of verifying one artifact")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON report")
    ap.add_argument("--gradient-sync", default=None,
                    help="BuildStrategy.gradient_sync mode the "
                    "program will run under (collective contract)")
    ap.add_argument("--feed", default=None,
                    help="comma-separated extra feed var names")
    ap.add_argument("--targets", default=None,
                    help="comma-separated fetch var names (enables "
                    "dead-op liveness; defaults to the artifact's "
                    "fetch_names)")
    ap.add_argument("--emit-journal", action="store_true",
                    help="also emit verifier_finding journal events")
    args = ap.parse_args(argv)

    if args.matrix:
        from paddle_tpu.analysis import composition_matrix
        report = composition_matrix()
        if args.json:
            print(json.dumps(report, indent=2))
        else:
            c = report["counts"]
            print("composition matrix: %d ok, %d rejected "
                  "(documented), %d BROKEN"
                  % (c["ok"], c["rejected"], c["broken"]))
            for combo in report["broken"]:
                print("  BROKEN guard=%s sync=%s pipelined=%s ps=%s"
                      % (combo["guard"], combo["gradient_sync"],
                         combo["pipelined"], combo["ps"]))
                for f in combo["findings"]:
                    if f["severity"] == "error":
                        print("    [%s] %s %s: %s"
                              % (f["severity"], f["rule"],
                                 f["citation"], f["message"]))
        return 2 if report["counts"]["broken"] else 0

    if not args.model:
        ap.error("need a model artifact path (or --matrix)")
    try:
        program, feed_names, fetch_names = load_program(args.model)
    except (OSError, pickle.UnpicklingError, KeyError) as e:
        print("verify_program: cannot load %r: %s"
              % (args.model, e), file=sys.stderr)
        return 1
    if args.feed:
        feed_names += [n for n in args.feed.split(",") if n]
    targets = [n for n in args.targets.split(",") if n] \
        if args.targets else (fetch_names or None)

    from paddle_tpu.analysis import (errors, format_findings,
                                     verify_program)
    findings = verify_program(program, feed=feed_names or None,
                              targets=targets,
                              gradient_sync=args.gradient_sync)
    if args.emit_journal:
        from paddle_tpu import observability as obs
        for f in findings:
            obs.emit("verifier_finding", stage="cli",
                     program_uid=program._uid, **f.to_dict())
    if args.json:
        print(json.dumps({
            "model": args.model,
            "findings": [f.to_dict() for f in findings],
            "errors": len(errors(findings)),
            "ok": not errors(findings),
        }, indent=2))
    else:
        print(format_findings(findings))
    return 2 if errors(findings) else 0


if __name__ == "__main__":
    sys.exit(main())
