#!/usr/bin/env python
"""Compare two+ BENCH_r*.json artifacts: per-metric value trajectory
with loud regression/hang flags — the OFFLINE complement to the
watchdog's online hang detection.

The repo's own history motivates this: BENCH_r01 measured 65.8k
tokens/s/chip, and by r05 the same row had silently degraded into a
240 s "backend hang" claim-timeout null. A value -> null transition is
exactly the failure a human scanning JSON blobs misses — this tool
calls it out as ``HANG`` and exits nonzero under ``--strict``.

Each artifact is the driver's wrapper shape ``{"n", "cmd", "rc",
"tail", "parsed"}``: every JSON line in ``tail`` is one metric row
(headline + --all extras + per-mix evidence), ``parsed`` is the
headline fallback when the tail has none. Bare ``{"metric": ...}``
JSONL files work too.

Flags per metric, per round transition:

  HANG        value -> null (or the metric vanished while its file
              reports an error) — the silent-timeout class
  REGRESSION  numeric drop beyond --threshold (default 20%) on
              higher-is-better metrics (heuristic: metrics whose unit
              mentions sec/latency/overhead/fraction are
              lower-is-better and flag on RISES instead)
  RECOVERED   null -> value
  NEW/GONE    the metric (dis)appeared between rounds

Usage:
    python tools/bench_diff.py BENCH_r01.json BENCH_r02.json ...
    python tools/bench_diff.py --json --strict BENCH_r*.json
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional

__all__ = ["load_rounds", "diff", "format_report"]

# explicit higher-is-better override, checked FIRST: cache hit rates
# and throughputs whose unit strings would otherwise trip the
# lower-is-better heuristic below (e.g. "hit fraction"). The PR 15
# metrics need no new entries — "qps" already covers
# qps_under_autoscale (name AND unit), and remediation_recovery is
# lower-is-better by both its "recovery" name and "seconds" unit —
# but both directions are pinned by tests/test_control.py. The
# sparse serving rows also need no new entries: sparse_serving_qps is
# higher-is-better by "qps" (name AND unit) and
# fresh_weight_to_served_ms lower-is-better by its "_ms" suffix (and
# "ms ..." unit) — both directions pinned by
# tests/test_sparse_serving.py. The step-engine rows likewise ride
# the existing patterns:
# composed_step_overhead is lower-is-better by its "overhead" name
# (and "% step time" unit), pipelined_sparse_throughput is
# higher-is-better by its "examples/sec" unit — both directions are
# pinned by tests/test_step_engine.py. The pipeline-stage rows (PR
# 19): pipeline_parallel_throughput rides "examples/sec"
# (higher-is-better), pipeline_bubble_fraction is lower-is-better by
# its "fraction" unit AND the explicit "bubble" token below (so a
# future rename of the unit string cannot silently flip it) — both
# directions pinned by tests/test_step_engine.py. The elastic rows are both
# lower-is-better via existing patterns — elastic_join_catchup by its
# "seconds" unit, reshard_bytes by its "bytes" unit — and both
# directions are pinned by tests/test_control.py. The PR 20
# join_commit_latency row is lower-is-better TWICE over ("latency"
# name and "seconds" unit); both directions are pinned by
# tests/test_control.py so neither pattern can silently rot.
_HIGHER_IS_BETTER = re.compile(
    r"(hit.?rate|hit.fraction|speedup|examples/sec|tokens/s|qps"
    r"|rows/s)",
    re.IGNORECASE)

# lower-is-better heuristic by unit/metric name: a drop in these is an
# improvement, a rise is the regression
_LOWER_IS_BETTER = re.compile(
    r"(seconds|_ms\b|latency|overhead|fraction|p9\d|bytes|recovery"
    r"|bubble)",
    re.IGNORECASE)


def _round_key(path: str, payload: dict):
    n = payload.get("n")
    if isinstance(n, int):
        return n
    m = re.search(r"r?(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else path


def _metric_key(row: dict) -> Optional[str]:
    metric = row.get("metric")
    if not metric:
        return None
    if "library" in row:  # per-mix evidence lines
        return "%s[%s]" % (metric, row["library"])
    return metric


def load_rounds(paths: List[str]) -> List[dict]:
    """[{round, path, rows: {metric_key: row}, error}] sorted by
    round."""
    out = []
    for path in paths:
        with open(path) as f:
            text = f.read()
        rows: Dict[str, dict] = {}
        file_error = None
        try:
            payload = json.loads(text)
        except ValueError:
            payload = None
        if isinstance(payload, dict) and ("tail" in payload
                                          or "parsed" in payload):
            rnd = _round_key(path, payload)
            for line in (payload.get("tail") or "").splitlines():
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                key = _metric_key(row)
                if key:
                    rows[key] = row
            parsed = payload.get("parsed")
            if isinstance(parsed, dict):
                key = _metric_key(parsed)
                if key and key not in rows:
                    rows[key] = parsed
            elif parsed is None and not rows:
                file_error = "no parsed headline (rc=%s)" \
                    % payload.get("rc")
        else:
            # bare JSONL of metric rows
            rnd = _round_key(path, {})
            for line in text.splitlines():
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                key = _metric_key(row)
                if key:
                    rows[key] = row
        out.append({"round": rnd, "path": path, "rows": rows,
                    "error": file_error})
    out.sort(key=lambda r: (isinstance(r["round"], str), r["round"]))
    return out


def _flag_transition(metric, prev, cur, threshold, cur_error=None):
    """-> (flag, note) for one metric between consecutive rounds
    (cur/prev are rows or None; ``cur_error`` is the newer ROUND's
    file-level failure, which makes a missing metric a hang, not a
    removal)."""
    pv = prev.get("value") if prev else None
    cv = cur.get("value") if cur else None
    if prev is None and cur is not None:
        if cv is None and cur.get("error"):
            return ("HANG", "appeared already dead: null value (%s)"
                    % cur["error"])
        return ("NEW", "appeared (value=%r)" % (cv,))
    if prev is not None and cur is None:
        if cur_error is not None:
            return ("HANG", "value %r -> whole round failed (%s)"
                    % (pv, cur_error)) if pv is not None else \
                   (None, None)
        return ("GONE", "metric vanished from this round")
    if pv is not None and cv is None:
        err = (cur.get("error") or "no value") if cur else "missing"
        return ("HANG", "value %r -> null (%s)" % (pv, err))
    if pv is None and cv is not None:
        return ("RECOVERED", "null -> %r" % (cv,))
    if pv is None and cv is None:
        return (None, None)
    try:
        pv_f, cv_f = float(pv), float(cv)
    except (TypeError, ValueError):
        return (None, None)
    if pv_f == 0:
        return (None, None)
    unit = (cur.get("unit") or "") + " " + metric
    lower_better = bool(_LOWER_IS_BETTER.search(unit)) \
        and not _HIGHER_IS_BETTER.search(unit)
    change = (cv_f - pv_f) / abs(pv_f)
    if not lower_better and change < -threshold:
        return ("REGRESSION", "%.4g -> %.4g (%.0f%%)"
                % (pv_f, cv_f, change * 100))
    if lower_better and change > threshold:
        return ("REGRESSION", "%.4g -> %.4g (+%.0f%% on a "
                "lower-is-better metric)" % (pv_f, cv_f, change * 100))
    return (None, None)


def diff(rounds: List[dict], threshold: float = 0.20) -> dict:
    """Per-metric trajectory + flagged transitions across the given
    rounds (already sorted)."""
    metrics = sorted({k for r in rounds for k in r["rows"]})
    trajectories = {}
    flags = []
    for m in metrics:
        traj = []
        for r in rounds:
            row = r["rows"].get(m)
            traj.append({"round": r["round"],
                         "value": row.get("value") if row else None,
                         "present": row is not None,
                         "error": row.get("error") if row else None})
        trajectories[m] = traj
        for a, b in zip(rounds, rounds[1:]):
            flag, note = _flag_transition(
                m, a["rows"].get(m), b["rows"].get(m), threshold,
                cur_error=b["error"])
            if flag:
                flags.append({"metric": m, "flag": flag,
                              "from_round": a["round"],
                              "to_round": b["round"], "note": note})
    order = {"HANG": 0, "REGRESSION": 1, "GONE": 2, "RECOVERED": 3,
             "NEW": 4}
    flags.sort(key=lambda f: (order.get(f["flag"], 9), f["metric"]))
    return {
        "rounds": [{"round": r["round"], "path": r["path"],
                    "metrics": len(r["rows"]), "error": r["error"]}
                   for r in rounds],
        "trajectories": trajectories,
        "flags": flags,
        "hangs": [f for f in flags if f["flag"] == "HANG"],
        "regressions": [f for f in flags
                        if f["flag"] == "REGRESSION"],
    }


def format_report(report: dict) -> str:
    lines = ["bench_diff: %d round(s): %s"
             % (len(report["rounds"]),
                ", ".join("r%s(%d rows)" % (r["round"], r["metrics"])
                          for r in report["rounds"]))]
    # flags first, LOUD — the whole point is that a hang cannot hide
    for f in report["flags"]:
        lines.append("!! %-10s %-45s r%s->r%s  %s"
                     % (f["flag"], f["metric"], f["from_round"],
                        f["to_round"], f["note"]))
    if not report["flags"]:
        lines.append("no flags: every shared metric held within "
                     "threshold")
    lines.append("")
    for m, traj in sorted(report["trajectories"].items()):
        vals = " -> ".join(
            ("%.4g" % t["value"]) if isinstance(t["value"],
                                                (int, float))
            else ("null" if t["present"] else "-")
            for t in traj)
        lines.append("  %-45s %s" % (m, vals))
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("files", nargs="+",
                    help="two or more BENCH_r*.json artifacts")
    ap.add_argument("--threshold", type=float, default=0.20,
                    help="relative change that counts as a "
                    "regression (default 0.20)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any HANG or REGRESSION flag "
                    "fires")
    args = ap.parse_args(argv)
    if len(args.files) < 2:
        ap.error("need at least two bench artifacts to diff")
    report = diff(load_rounds(args.files), threshold=args.threshold)
    if args.json:
        print(json.dumps(report, indent=2, default=repr))
    else:
        print(format_report(report))
    if args.strict and (report["hangs"] or report["regressions"]):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
