"""Input-pipeline smoke probe: pipelined (chunked-scan + background
prefetch) vs per-step data-fed training on a synthetic workload,
JSON to stdout.

The synthetic "reader" manufactures each batch on the host (PRNG fill
plus ``--host-work`` tanh passes standing in for decode/augment cost),
so the probe measures the thing the pipeline exists to hide: host
batch production and H2D transfer. Two protocols over the SAME
generator and model:

- **baseline**: one blocking ``Executor.run`` per step, batch made
  synchronously before each dispatch — its stall fraction is the
  share of wall time spent making/transferring batches while the
  device idles.
- **pipelined**: ``DevicePrefetcher`` stacks ``--chunk-size`` batches
  and pre-transfers them on a background thread while
  ``Executor.run_pipelined`` consumes the previous chunk in ONE
  compiled lax.scan dispatch — its stall fraction comes from
  ``DevicePrefetcher.stats()`` (consumer time blocked waiting for the
  host).

Used by ``bench.py``'s ``pipelined_train_throughput`` row (imported,
so the bench row and this tool can never measure different things).

    python tools/pipeline_probe.py [--steps N] [--batch B]
        [--chunk-size K] [--depth D] [--host-work W]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

_WIDTH = 784
_HIDDEN = 256


def build_mlp(seed=5):
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[_WIDTH], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        hidden = img
        for h in (_HIDDEN, _HIDDEN):
            hidden = layers.fc(hidden, size=h, act="relu")
        pred = layers.fc(hidden, size=10, act="softmax")
        loss = layers.mean(layers.cross_entropy(pred, label))
        fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
    return main, startup, loss


def synthetic_batches(steps, batch, host_work, seed=0):
    """Per-step host batch manufacture with a tunable decode-cost
    stand-in (each tanh pass re-touches the whole batch)."""
    rs = np.random.RandomState(seed)
    for _ in range(steps):
        img = rs.rand(batch, _WIDTH).astype(np.float32)
        for _ in range(host_work):
            img = np.tanh(img)
        yield {"img": img,
               "label": rs.randint(0, 10, (batch, 1))
               .astype(np.int64)}


def run_baseline(steps, batch, host_work, warm_steps):
    """Per-step protocol: make batch (device idle: stall), transfer,
    dispatch; ONE final readback syncs the whole chain."""
    import jax

    import paddle_tpu as fluid

    main, startup, loss = build_mlp()
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        # warmup compile outside the timed window; same warm_steps as
        # the pipelined protocol (one chunk) so both timed sections
        # start from the same trained state and the final losses stay
        # comparable
        for warm in synthetic_batches(warm_steps, batch, host_work,
                                      seed=1):
            exe.run(main, feed=warm, fetch_list=[loss])
        d0 = exe.dispatch_count
        gen = synthetic_batches(steps, batch, host_work)
        stall = 0.0
        out = None
        t_start = time.perf_counter()
        while True:
            t0 = time.perf_counter()
            try:
                feed = next(gen)
            except StopIteration:
                break
            dev = {k: jax.device_put(v) for k, v in feed.items()}
            for v in dev.values():
                v.block_until_ready()
            stall += time.perf_counter() - t0
            out = exe.run(main, feed=dev, fetch_list=[loss],
                          return_numpy=False)
        final = float(np.asarray(out[0]).reshape(-1)[0])
        total = time.perf_counter() - t_start
        dispatches = exe.dispatch_count - d0
    if not np.isfinite(final):
        raise FloatingPointError("non-finite baseline loss")
    return {"protocol": "per_step", "steps": steps,
            "steps_per_s": round(steps / total, 2),
            "stall_fraction": round(stall / total, 4),
            "dispatches": dispatches, "final_loss": final}


def run_pipelined(steps, batch, host_work, chunk_size, depth):
    """Chunked protocol: background stack+H2D (DevicePrefetcher) feeds
    one scan dispatch per chunk; ONE final readback syncs."""
    import paddle_tpu as fluid

    main, startup, loss = build_mlp()
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        # warm with a REAL [K, ...] chunk: the scan is cached per
        # chunk shape, so a placeholder shape would leave the compile
        # inside the timed window
        from paddle_tpu.pyreader import stack_batches
        warm = list(synthetic_batches(chunk_size, batch, host_work,
                                      seed=1))
        exe.run_pipelined(main, feed_chunk=stack_batches(warm),
                          fetch_list=[loss])
        d0, c0 = exe.dispatch_count, exe.compile_count
        gen = synthetic_batches(steps, batch, host_work)
        out = None
        t_start = time.perf_counter()
        with fluid.DevicePrefetcher(gen, chunk_size,
                                    depth=depth) as pf:
            for chunk, _k in pf:
                out = exe.run_pipelined(main, feed_chunk=chunk,
                                        fetch_list=[loss],
                                        return_numpy=False)
        final = float(np.asarray(out[0]).reshape(-1)[0])
        total = time.perf_counter() - t_start
        stats = pf.stats()
        dispatches = exe.dispatch_count - d0
        compiles = exe.compile_count - c0
    if not np.isfinite(final):
        raise FloatingPointError("non-finite pipelined loss")
    return {"protocol": "pipelined", "steps": steps,
            "chunk_size": chunk_size, "depth": depth,
            "steps_per_s": round(steps / total, 2),
            "stall_fraction": stats["stall_fraction"],
            "stall_s": stats["stall_s"], "h2d_s": stats["h2d_s"],
            "dispatches": dispatches, "chunk_compiles": compiles,
            "final_loss": final}


def probe(steps=64, batch=256, chunk_size=8, depth=2, host_work=4):
    baseline = run_baseline(steps, batch, host_work,
                            warm_steps=chunk_size)
    pipelined = run_pipelined(steps, batch, host_work, chunk_size,
                              depth)
    speedup = None
    if baseline["steps_per_s"]:
        speedup = round(pipelined["steps_per_s"]
                        / baseline["steps_per_s"], 3)
    return {"tool": "pipeline_probe", "batch": batch,
            "host_work": host_work,
            "pipelined": pipelined, "baseline": baseline,
            "speedup_vs_per_step": speedup}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--chunk-size", type=int, default=8)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--host-work", type=int, default=4)
    args = ap.parse_args(argv)
    print(json.dumps(probe(steps=args.steps, batch=args.batch,
                           chunk_size=args.chunk_size,
                           depth=args.depth,
                           host_work=args.host_work)))


if __name__ == "__main__":
    main()
